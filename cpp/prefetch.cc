// Native threaded chunk prefetcher for dmlc_core_tpu.
//
// Reference parity: src/io/threaded_input_split.h :: ThreadedInputSplit +
// include/dmlc/threadediter.h :: ThreadedIter (SURVEY.md §2a/2b) — a
// producer thread reads byte-range chunks from a list of file segments into
// a bounded queue, overlapping storage reads with the Python-side record
// extraction and parse (thread boundary #1 of the data pipeline).
//
// Chunks never span files (records never span files in the InputSplit
// contract), and the chunk sequence is byte-identical to the Python
// InputSplitBase sequential read path so both produce the same shards.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Chunk {
  char* data = nullptr;
  int64_t len = 0;
  int32_t fidx = -1;
};

struct Segment {
  std::string path;
  int64_t begin;
  int64_t end;
};

struct Prefetch {
  std::vector<Segment> segments;
  int64_t chunk_size;
  size_t capacity;

  std::mutex mu;
  std::condition_variable not_full;
  std::condition_variable not_empty;
  std::deque<Chunk> queue;
  bool done = false;      // producer finished (EOF or error)
  bool shutdown = false;  // consumer requested stop
  std::string error;
  std::thread worker;

  void Produce() {
    for (size_t si = 0; si < segments.size(); ++si) {
      const Segment& seg = segments[si];
      std::FILE* f = std::fopen(seg.path.c_str(), "rb");
      if (f == nullptr) {
        Fail("cannot open " + seg.path);
        return;
      }
      if (std::fseek(f, static_cast<long>(seg.begin), SEEK_SET) != 0) {
        std::fclose(f);
        Fail("seek failed in " + seg.path);
        return;
      }
      int64_t pos = seg.begin;
      while (pos < seg.end) {
        const int64_t want = std::min(chunk_size, seg.end - pos);
        char* buf = static_cast<char*>(std::malloc(static_cast<size_t>(want)));
        if (buf == nullptr) {
          std::fclose(f);
          Fail("out of memory");
          return;
        }
        const size_t got = std::fread(buf, 1, static_cast<size_t>(want), f);
        if (got == 0) {
          std::free(buf);
          std::fclose(f);
          Fail("short read in " + seg.path);
          return;
        }
        pos += static_cast<int64_t>(got);
        Chunk c{buf, static_cast<int64_t>(got), static_cast<int32_t>(si)};
        std::unique_lock<std::mutex> lk(mu);
        not_full.wait(lk, [&] { return queue.size() < capacity || shutdown; });
        if (shutdown) {
          std::free(buf);
          std::fclose(f);
          return;
        }
        queue.push_back(c);
        not_empty.notify_one();
      }
      std::fclose(f);
    }
    std::lock_guard<std::mutex> lk(mu);
    done = true;
    not_empty.notify_all();
  }

  void Fail(const std::string& msg) {
    std::lock_guard<std::mutex> lk(mu);
    error = msg;
    done = true;
    not_empty.notify_all();
  }
};

}  // namespace

extern "C" {

void* dmlc_prefetch_open(const char* const* paths, const int64_t* begins,
                         const int64_t* ends, int32_t n_files,
                         int64_t chunk_size, int32_t capacity) {
  auto* p = new Prefetch();
  p->segments.reserve(n_files);
  for (int32_t i = 0; i < n_files; ++i) {
    p->segments.push_back(Segment{paths[i], begins[i], ends[i]});
  }
  p->chunk_size = chunk_size > 0 ? chunk_size : (int64_t(1) << 20);
  p->capacity = capacity > 0 ? static_cast<size_t>(capacity) : 8;
  p->worker = std::thread([p] { p->Produce(); });
  return p;
}

// 1 = chunk delivered, 0 = clean EOF, -1 = producer error (see _error).
int dmlc_prefetch_next(void* h, char** out_data, int64_t* out_len,
                       int32_t* out_fidx) {
  auto* p = static_cast<Prefetch*>(h);
  std::unique_lock<std::mutex> lk(p->mu);
  p->not_empty.wait(lk, [&] { return !p->queue.empty() || p->done; });
  if (p->queue.empty()) {
    return p->error.empty() ? 0 : -1;
  }
  Chunk c = p->queue.front();
  p->queue.pop_front();
  p->not_full.notify_one();
  *out_data = c.data;
  *out_len = c.len;
  *out_fidx = c.fidx;
  return 1;
}

void dmlc_prefetch_free(char* data) { std::free(data); }

const char* dmlc_prefetch_error(void* h) {
  auto* p = static_cast<Prefetch*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  return p->error.c_str();
}

void dmlc_prefetch_close(void* h) {
  auto* p = static_cast<Prefetch*>(h);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->shutdown = true;
    p->not_full.notify_all();
  }
  if (p->worker.joinable()) p->worker.join();
  for (Chunk& c : p->queue) std::free(c.data);
  delete p;
}

}  // extern "C"
